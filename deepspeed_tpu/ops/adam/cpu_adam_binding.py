"""ctypes signatures for the native cpu_adam kernels (csrc/cpu_adam.cpp).

Reference parity: the pybind11 export block ``csrc/adam/cpu_adam.cpp:290-303``.
"""

from __future__ import annotations

import numpy as np

from deepspeed_tpu.ops import native
from deepspeed_tpu.ops.native import c_f32, c_f32p, c_i64, c_int, c_u16p

_configured = False


def _lib():
    global _configured
    lib = native.get_lib()
    if not _configured:
        lib.ds_adam_step.argtypes = [c_f32p, c_f32p, c_f32p, c_f32p, c_i64,
                                     c_f32, c_f32, c_f32, c_f32, c_f32, c_int, c_f32, c_f32]
        lib.ds_adam_step_bf16.argtypes = [c_f32p, c_u16p, c_f32p, c_f32p, c_u16p, c_i64,
                                          c_f32, c_f32, c_f32, c_f32, c_f32, c_int, c_f32, c_f32]
        lib.ds_adam_step_plus_copy.argtypes = [c_f32p, c_f32p, c_f32p, c_f32p, c_u16p, c_i64,
                                               c_f32, c_f32, c_f32, c_f32, c_f32, c_int, c_f32, c_f32]
        _configured = True
    return lib


def adam_step(params: np.ndarray, grads: np.ndarray, exp_avg: np.ndarray,
              exp_avg_sq: np.ndarray, *, lr: float, beta1: float, beta2: float,
              eps: float, weight_decay: float, adamw_mode: bool, step: int,
              param_out_bf16: np.ndarray | None = None) -> None:
    """In-place fused Adam update on contiguous fp32 host buffers.

    ``grads`` may be fp32 or bf16-as-uint16; if ``param_out_bf16`` is given the
    updated params are also stored as bf16 into it (fused convert+copy for the
    device-bound staging buffer).
    """
    native.check_buffer(params, np.float32, "params")
    native.check_buffer(exp_avg, np.float32, "exp_avg", params.size)
    native.check_buffer(exp_avg_sq, np.float32, "exp_avg_sq", params.size)
    if grads.dtype not in (np.float32, np.uint16):
        raise TypeError(f"grads must be float32 or bf16-as-uint16, got {grads.dtype}")
    native.check_buffer(grads, grads.dtype.type, "grads", params.size)
    if param_out_bf16 is not None:
        native.check_buffer(param_out_bf16, np.uint16, "param_out_bf16", params.size)
    n = params.size
    bias_c1 = float(1.0 - beta1**step)
    bias_c2 = float(1.0 - beta2**step)
    lib = _lib()
    common = (n, lr, beta1, beta2, eps, weight_decay, int(adamw_mode), bias_c1, bias_c2)
    if grads.dtype == np.uint16:
        out_ptr = native.as_u16_ptr(param_out_bf16) if param_out_bf16 is not None else None
        lib.ds_adam_step_bf16(native.as_f32_ptr(params), native.as_u16_ptr(grads),
                              native.as_f32_ptr(exp_avg), native.as_f32_ptr(exp_avg_sq),
                              out_ptr, *common)
    elif param_out_bf16 is not None:
        lib.ds_adam_step_plus_copy(native.as_f32_ptr(params), native.as_f32_ptr(grads),
                                   native.as_f32_ptr(exp_avg), native.as_f32_ptr(exp_avg_sq),
                                   native.as_u16_ptr(param_out_bf16), *common)
    else:
        lib.ds_adam_step(native.as_f32_ptr(params), native.as_f32_ptr(grads),
                         native.as_f32_ptr(exp_avg), native.as_f32_ptr(exp_avg_sq), *common)
