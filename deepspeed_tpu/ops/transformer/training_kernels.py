"""Fused training transformer layer — named-op surface for the reference's
``DeepSpeedTransformerLayer`` CUDA stack (``csrc/transformer/
ds_transformer_cuda.cpp`` orchestrating normalize/softmax/dropout/gelu/
transform kernels; Python wrapper ``deepspeed/ops/transformer/transformer.py:294``).

On TPU the fusion the CUDA stack hand-schedules is exactly what XLA does to
a jitted block: layernorm/bias/gelu fuse into the surrounding matmuls, and
attention runs the Pallas flash kernel. So the named op is a jit-compiled
closure over :func:`deepspeed_tpu.models.transformer.block` — one compiled
program per config, matching the reference's one-cuda-graph-per-layer-config
model. The stochastic variant (``stochastic_mode`` — the reference trades
determinism for speed) maps to stochastic-rounding quantized activations via
:mod:`deepspeed_tpu.ops.quantizer.kernels` when requested.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.utils.init_on_device import honors_on_device

__all__ = ["DeepSpeedTransformerConfig", "DeepSpeedTransformerLayer"]


@functools.partial(jax.jit, static_argnames=("cfg",))
def _block_fwd(cfg, params, x, positions, mask_bias):
    return T.block(cfg, x, params, positions, mask_bias)


@dataclasses.dataclass
class DeepSpeedTransformerConfig:
    """Mirror of the reference config surface (``transformer.py:32``) with
    the knobs that exist on TPU (dropout is a model-level concern in the
    functional zoo; fp16 → bf16)."""
    batch_size: int = 1
    hidden_size: int = 768
    heads: int = 12
    intermediate_size: Optional[int] = None
    seq_length: int = 512
    layer_norm_eps: float = 1e-12
    pre_layer_norm: bool = True
    stochastic_mode: bool = False
    attn_dropout_ratio: float = 0.0   # accepted for parity; dropout is a
    hidden_dropout_ratio: float = 0.0  # training-loop concern in the zoo


class DeepSpeedTransformerLayer:
    """Callable fused encoder layer: ``layer(params, x, mask_bias=None)``.

    ``params`` is one layer subtree in the zoo layout
    (``models/transformer.init_params(...)["layers"]`` sliced to one layer).
    The first call compiles; later calls hit the jit cache — the analogue of
    the reference's ``create_transformer_layer_*`` + per-layer workspace.
    """

    def __init__(self, config: DeepSpeedTransformerConfig):
        self.config = config
        d_ff = config.intermediate_size or 4 * config.hidden_size
        self._cfg = T.TransformerConfig(
            vocab_size=1, max_seq=config.seq_length, n_layer=1,
            n_head=config.heads, d_model=config.hidden_size, d_ff=d_ff,
            causal=False, norm="layernorm", activation="gelu",
            norm_eps=config.layer_norm_eps, attn_bias=True,
            pos_embedding="none")

        # bound method over the shared module-level jit: N identically
        # configured layers share ONE compiled program (cfg is a hashable
        # static arg), matching the reference's per-config CUDA graph
        self._fwd = functools.partial(_block_fwd, self._cfg)
        self._step = 0
        # distinct per-instance stream so stacked layers at the same step
        # don't share a rounding realization
        DeepSpeedTransformerLayer._instances += 1
        self._seed_offset = 104729 * DeepSpeedTransformerLayer._instances

    _instances = 0

    def __call__(self, params, x, mask_bias=None, seed=None):
        """``seed`` (int or traced scalar) selects the stochastic-rounding
        stream. IMPORTANT for stochastic_mode under an outer ``jax.jit``:
        pass the step counter as ``seed`` explicitly — the internal
        eager-mode counter would be baked in at trace time."""
        B, S, D = x.shape
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :], (B, S))
        if self.config.stochastic_mode:
            from deepspeed_tpu.ops.quantizer.kernels import ds_sr_quantize
            # a fresh seed per call: SR's error-averaging needs a different
            # rounding realization every step
            if seed is None:
                seed, self._step = self._step, self._step + 1
            x = ds_sr_quantize(x, groups=B, bits=16,
                               seed=self._seed_offset + seed)
        return self._fwd(params, x, positions, mask_bias)

    @honors_on_device
    def init_params(self, rng):
        full = T.init_params(self._cfg, rng)
        return jax.tree.map(lambda a: a[0], full["layers"])
