"""Inference transformer op surface — the named-op home of the generative
decode path (reference ``csrc/transformer/inference/csrc/pt_binding.cpp``
export list :1668-1793: ``qkv_gemm``, ``softmax_context`` (KV-append +
attention), ``mlp_gemm``, ``residual_add_bias``, rotary embedding,
workspace ``allocate_workspace_*``).

On TPU the gemm+bias+norm fusions are XLA's job; the ops that need names
are the ones with real machinery behind them:

- ``softmax_context`` → :func:`decode_attention` (Pallas flash-decode over
  the KV cache, ``ops/pallas/decode_attention.py``);
- workspace management → :func:`init_kv_cache` +
  ``inference/engine.py``'s persistent bucketed decode workspace;
- rotary embedding → the zoo's :func:`apply_rotary_pos_emb`;
- the whole per-layer pipeline → :func:`forward_cached` (prefill + decode
  against the cache in one jitted program).
"""

from __future__ import annotations

from deepspeed_tpu.models.transformer import (forward_cached, init_kv_cache)
from deepspeed_tpu.ops.pallas.decode_attention import decode_attention


def apply_rotary_pos_emb(x, positions, theta: float = 10000.0):
    """Rotary embedding on [B, T, H, Hd] at the given absolute positions
    (reference ``apply_rotary_pos_emb.cu``)."""
    from deepspeed_tpu.models.transformer import _rope
    return _rope(x, positions, theta)


def softmax_context(q, ck, cv, pos, *, pad_bias=None, alibi_slopes=None):
    """Reference-named alias for the fused decode attention op
    (``pt_binding.cpp`` ``softmax_context``: attention of new tokens against
    the appended KV cache). Single-token decode form."""
    out = decode_attention(q, ck, cv, pos, pad_bias=pad_bias,
                           alibi_slopes=alibi_slopes)
    if out is None:
        raise ValueError("shape outside the decode kernel envelope; use "
                         "models.transformer.forward_cached (einsum fallback)")
    return out


__all__ = ["forward_cached", "init_kv_cache", "decode_attention",
           "softmax_context", "apply_rotary_pos_emb"]
