"""Random-LTD op surface (reference ``deepspeed/ops/random_ltd/dropping_utils.py``
backed by ``csrc/random_ltd/{token_sort,gather_scatter,slice_attn_masks}.cu``).

The CUDA kernels exist because torch needs a comparison-free device sort and
explicit gather/scatter launches; on TPU these are ``jax.random.permutation``
+ ``jnp.take``/``dynamic_update`` which XLA schedules natively, so this
module is the named-op façade over
:mod:`deepspeed_tpu.runtime.data_pipeline.data_routing.basic_layer` plus the
reference's sampling entry points.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.data_pipeline.data_routing.basic_layer import (
    gather_tokens, scatter_tokens, slice_attention_mask, token_sample)

__all__ = ["gpt_sample_tokens", "bert_sample_tokens", "gather_tokens",
           "scatter_tokens", "slice_attention_mask", "token_sample"]


def gpt_sample_tokens(reserved_length: int, seq_length: int, batch_size: int,
                      layers: int = 1, rng=None, attn_mask=None):
    """Per-layer sorted token subsets for causal models (reference
    ``dropping_utils.py:16``): one index set per layer, shared across the
    batch; the causal mask is sliced to the kept tokens.

    Returns ``(indices [layers, reserved], sliced_mask or None)``.
    """
    rng = jax.random.key(0) if rng is None else rng
    keys = jax.random.split(rng, layers)
    idx = jnp.stack([token_sample(k, seq_length, reserved_length) for k in keys])
    mask = None
    if attn_mask is not None:
        mask = jnp.stack([slice_attention_mask(attn_mask, idx[l])
                          for l in range(layers)])
    return idx, mask


def bert_sample_tokens(reserved_length: int, seq_length: int, batch_size: int,
                       layers: int = 1, rng=None, attn_mask=None):
    """Per-(layer, batch) sorted subsets for bidirectional models (reference
    ``dropping_utils.py:50``: each sequence samples independently).

    Returns ``(indices [layers, batch, reserved], sliced_mask or None)``.
    """
    rng = jax.random.key(0) if rng is None else rng
    keys = jax.random.split(rng, layers * batch_size).reshape(layers, batch_size)
    idx = jnp.stack([
        jnp.stack([token_sample(keys[l, b], seq_length, reserved_length)
                   for b in range(batch_size)])
        for l in range(layers)])
    mask = None
    if attn_mask is not None:
        mask = jnp.stack([
            jnp.stack([slice_attention_mask(attn_mask[b:b + 1], idx[l, b])[0]
                       for b in range(batch_size)])
            for l in range(layers)])
    return idx, mask
