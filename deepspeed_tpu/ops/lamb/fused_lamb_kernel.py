"""Pallas fused LAMB — TPU-native named op for the reference's fused LAMB
(``csrc/lamb/fused_lamb_cuda_kernel.cu``: fused moment update + per-layer
trust-ratio norm reductions; Python wrapper ``deepspeed/ops/lamb/fused_lamb.py``).

LAMB is Adam plus a per-LAYER trust ratio ``||p|| / ||update||`` — the
norms are full-tensor reductions, which is why the reference needs a
dedicated two-stage CUDA kernel (blockwise reduce + final reduce). The
TPU design does it in ONE pass: the kernel streams p/g/m/v tile-by-tile,
emits the un-scaled update u = m̂/(√v̂+ε) + wd·p together with new
moments, and accumulates Σp² and Σu² into an SMEM scalar block that
persists across the sequential grid (TPU grids are sequential, so
accumulate-into-output is race-free). The final ``p - lr·ratio·u`` is a
trivially-fused XLA elementwise op — no second pass over HBM for the
reduction itself.

Call surfaces mirror :mod:`deepspeed_tpu.ops.adam.fused_adam_kernel`:
:func:`fused_lamb_step` (flat 1-D buffers, one "layer" per call) and
:func:`fused_lamb` (optax wrapper, config name ``FusedLamb`` — trust
ratio per pytree leaf, matching optax.lamb semantics for drop-in tests).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BLOCK_ROWS = 256
_LANES = 128
_BLOCK = _BLOCK_ROWS * _LANES


def _lamb_kernel(sc_ref, p_ref, g_ref, m_ref, v_ref,
                 u_ref, mo_ref, vo_ref, norms_ref,
                 *, b1, b2, eps, wd):
    """One tile: moments + un-scaled LAMB update + running Σp²/Σu².

    sc_ref (SMEM f32[2]): [1-b1^t, 1-b2^t]. Pad elements need no masking:
    they are zeros in p/g/m/v, so they contribute 0 to both norms and to u
    (0/(√0+ε)=0).
    """
    bc1, bc2 = sc_ref[0], sc_ref[1]
    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * (g * g)
    u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if wd:
        u = u + wd * p
    u_ref[:] = u
    mo_ref[:] = m
    vo_ref[:] = v

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        norms_ref[0, 0] = 0.0
        norms_ref[0, 1] = 0.0

    norms_ref[0, 0] += jnp.sum(p * p)
    norms_ref[0, 1] += jnp.sum(u * u)


@functools.partial(jax.jit, static_argnames=("b1", "b2", "eps", "wd", "emit",
                                             "interpret"))
def _fused_lamb_flat(p, g, m, v, lr, bc1, bc2, *, b1, b2, eps, wd, emit,
                     interpret):
    n = p.shape[0]
    pad = (-n) % _BLOCK
    padded = n + pad

    def prep(x):
        x = jnp.pad(x, (0, pad)) if pad else x
        return x.reshape(padded // _LANES, _LANES)

    rows = padded // _LANES
    grid = (rows // _BLOCK_ROWS,)
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i, sc: (i, 0))
    scalars = jnp.stack([bc1, bc2]).astype(jnp.float32)
    kern = functools.partial(_lamb_kernel, b1=b1, b2=b2, eps=eps, wd=wd)
    u, mo, vo, norms = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[spec] * 4,
            out_specs=[spec] * 3 + [pl.BlockSpec((1, 2), lambda i, sc: (0, 0),
                                                 memory_space=pltpu.SMEM)],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((1, 2), jnp.float32),
        ],
        interpret=interpret,
    )(scalars, prep(p), prep(g), prep(m.astype(jnp.float32)),
      prep(v.astype(jnp.float32)))

    p_norm = jnp.sqrt(norms[0, 0])
    u_norm = jnp.sqrt(norms[0, 1])
    # optax/reference semantics: ratio 1.0 when either norm is zero
    ratio = jnp.where((p_norm > 0.0) & (u_norm > 0.0), p_norm / u_norm, 1.0)

    def unprep(x):
        flat = x.reshape(-1)
        return flat[:n] if pad else flat

    u = unprep(u)
    # emit="update": callers apply ratio*u themselves — don't burn a
    # param-sized multiply + cast + HBM write on a discarded new_p
    new_p = ((p.astype(jnp.float32) - lr * ratio * u).astype(p.dtype)
             if emit == "param" else None)
    return new_p, unprep(mo), unprep(vo), ratio, u


@functools.partial(jax.jit, static_argnames=("b1", "b2", "eps", "wd", "emit"))
def _jnp_lamb_flat(p, g, m, v, lr, bc1, bc2, *, b1, b2, eps, wd, emit):
    """Kernel math in plain jnp — off-TPU fallback (see fused_adam).
    Returns ``(new_p, m, v, ratio, u)`` like :func:`_fused_lamb_flat`."""
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    m = b1 * m.astype(jnp.float32) + (1.0 - b1) * g
    v = b2 * v.astype(jnp.float32) + (1.0 - b2) * (g * g)
    u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if wd:
        u = u + wd * pf
    p_norm = jnp.linalg.norm(pf)
    u_norm = jnp.linalg.norm(u)
    ratio = jnp.where((p_norm > 0.0) & (u_norm > 0.0), p_norm / u_norm, 1.0)
    new_p = (pf - lr * ratio * u).astype(p.dtype) if emit == "param" else None
    return new_p, m, v, ratio, u


def _run_lamb(p, g, m, v, *, step, lr, b1, b2, eps, weight_decay,
              bias_correction, interpret, emit="param"):
    # interpret=None: compiled kernel on TPU, jnp elsewhere; True: kernel in
    # interpret mode; False: compiled kernel on any backend.
    use_kernel = True if interpret is not None else jax.default_backend() == "tpu"
    step = jnp.asarray(step, jnp.float32)
    if bias_correction:
        bc1 = 1.0 - jnp.asarray(b1, jnp.float32) ** step
        bc2 = 1.0 - jnp.asarray(b2, jnp.float32) ** step
    else:
        bc1 = jnp.float32(1.0)
        bc2 = jnp.float32(1.0)
    kw = dict(b1=float(b1), b2=float(b2), eps=float(eps), wd=float(weight_decay),
              emit=emit)
    lr = jnp.asarray(lr, jnp.float32)
    if not use_kernel:
        return _jnp_lamb_flat(p, g, m, v, lr, bc1, bc2, **kw)
    return _fused_lamb_flat(p, g, m, v, lr, bc1, bc2,
                            interpret=bool(interpret), **kw)


def fused_lamb_step(p, g, m, v, *, step, lr, b1=0.9, b2=0.999, eps=1e-6,
                    weight_decay=0.0, bias_correction=True,
                    interpret: Optional[bool] = None):
    """Single fused LAMB step on one flat layer buffer.

    Returns ``(new_p, new_m, new_v, trust_ratio)``. ``interpret``: None
    (default) = compiled Pallas kernel on TPU, identical jnp math elsewhere;
    True = kernel in interpret mode (kernel unit tests); False = force the
    compiled kernel on any backend.
    """
    new_p, nm, nv, ratio, _ = _run_lamb(
        p, g, m, v, step=step, lr=lr, b1=b1, b2=b2, eps=eps,
        weight_decay=weight_decay, bias_correction=bias_correction,
        interpret=interpret)
    return new_p, nm, nv, ratio


class FusedLambState(NamedTuple):
    count: jax.Array
    mu: optax.Updates
    nu: optax.Updates


def fused_lamb(learning_rate=None, b1=0.9, b2=0.999, eps=1e-6,
               weight_decay=0.0, bias_correction=True,
               interpret: Optional[bool] = None) -> optax.GradientTransformationExtraArgs:
    """Optax-compatible fused LAMB (per-leaf trust ratio, like optax.lamb)."""

    def init(params):
        # param-shaped fp32 moments (see fused_adam: ZeRO/TP sharding + ckpt
        # layouts stay uniform; ravel is free inside jit)
        zeros = jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), params)
        return FusedLambState(count=jnp.zeros((), jnp.int32),
                              mu=zeros, nu=jax.tree.map(jnp.copy, zeros))

    def update(grads, state, params=None, **extra):
        if params is None:
            raise ValueError("fused_lamb requires params (trust ratio needs ||p||)")
        count = state.count + 1
        leaves_p, treedef = jax.tree.flatten(params)
        leaves_g = treedef.flatten_up_to(grads)
        leaves_m = treedef.flatten_up_to(state.mu)
        leaves_v = treedef.flatten_up_to(state.nu)
        out_u, out_m, out_v = [], [], []
        for p, g, m, v in zip(leaves_p, leaves_g, leaves_m, leaves_v):
            # use the kernel's own u and ratio — no new_p - p reconstruction
            # (saves a pass over p and avoids bf16 cancellation)
            _, nm, nv, ratio, u = _run_lamb(
                p.reshape(-1), g.reshape(-1), m.reshape(-1), v.reshape(-1),
                step=count, lr=0.0, emit="update",
                b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
                bias_correction=bias_correction, interpret=interpret)
            u = (ratio * u).reshape(p.shape)
            if learning_rate is not None:
                # standard optax deltas (apply_updates adds); None => engine
                # applies p - lr*u with its scheduled lr. Schedules (callables
                # of the step count) are resolved here like optax does.
                # optax evaluates schedules at the 0-based pre-increment
                # count; our count is 1-based
                lr_t = (learning_rate(count - 1) if callable(learning_rate)
                        else learning_rate)
                u = (-lr_t * u).astype(p.dtype)
            out_u.append(u)
            out_m.append(nm.reshape(p.shape))
            out_v.append(nv.reshape(p.shape))
        updates = jax.tree.unflatten(treedef, out_u)
        new_state = FusedLambState(count=count,
                                   mu=jax.tree.unflatten(treedef, out_m),
                                   nu=jax.tree.unflatten(treedef, out_v))
        return updates, new_state

    return optax.GradientTransformationExtraArgs(init, update)
