from deepspeed_tpu.ops.lamb.fused_lamb_kernel import fused_lamb, fused_lamb_step

__all__ = ["fused_lamb", "fused_lamb_step"]
