"""Op-builder registry.

Reference parity: ``op_builder/builder.py`` + ``accelerator.create_op_builder``
— a named registry of kernel families with compatibility probing and lazy
loading. On TPU there is no JIT C++ compilation against torch; device ops are
Pallas/XLA (imported lazily, compiled by XLA on first trace) and host ops are
C++ shared libraries built once via ``make`` and loaded with ctypes.

Builder names keep the reference spelling (``CPUAdamBuilder`` etc.) so code
and configs that probe ops by name port over.
"""

from __future__ import annotations

import importlib
import os
import subprocess
from typing import Dict, Optional, Type

from deepspeed_tpu.utils.logging import logger


class OpBuilder:
    """Base op builder: probe availability + load the op module."""

    BUILD_VAR = "DS_BUILD_OPS"
    NAME = "op"
    # python module (relative to deepspeed_tpu) that implements the op family
    MODULE: Optional[str] = None

    def __init__(self):
        self.error_log: Optional[str] = None

    def is_compatible(self, verbose: bool = True) -> bool:
        if self.MODULE is None:
            return False
        try:
            importlib.import_module(self.MODULE)
            return True
        except Exception as e:  # pragma: no cover - env specific
            self.error_log = str(e)
            if verbose:
                logger.warning(f"op {self.NAME} incompatible: {e}")
            return False

    def load(self, verbose: bool = True):
        if self.MODULE is None:
            raise RuntimeError(f"Op {self.NAME} has no implementation module")
        return importlib.import_module(self.MODULE)

    def builder_available(self) -> bool:
        return self.is_compatible(verbose=False)


class NativeOpBuilder(OpBuilder):
    """Host-side C++ op loaded via ctypes from a shared library.

    The library is built from ``csrc/`` with ``make`` (no torch cpp_extension
    involved). ``load()`` triggers a build if the .so is missing.
    """

    LIBRARY = "libdstpu.so"

    def lib_path(self) -> str:
        root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        return os.path.join(root, "csrc", "build", self.LIBRARY)

    def build(self, verbose: bool = True) -> bool:
        root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        csrc = os.path.join(root, "csrc")
        if not os.path.exists(os.path.join(csrc, "Makefile")):
            self.error_log = "csrc/Makefile not found"
            return False
        try:
            subprocess.run(["make", "-C", csrc, "-j"], check=True,
                           capture_output=not verbose)
            return True
        except subprocess.CalledProcessError as e:  # pragma: no cover
            self.error_log = f"native build failed: {e}"
            return False

    def is_compatible(self, verbose: bool = True) -> bool:
        if os.path.exists(self.lib_path()):
            return True
        return self.build(verbose=verbose)

    def load(self, verbose: bool = True):
        if not os.path.exists(self.lib_path()):
            if not self.build(verbose=verbose):
                raise RuntimeError(f"Could not build native library for {self.NAME}: {self.error_log}")
        mod = importlib.import_module(self.MODULE)
        return mod


# --------------------------------------------------------------------- #
# Concrete builders (names mirror op_builder/*.py)

class CPUAdamBuilder(NativeOpBuilder):
    NAME = "cpu_adam"
    MODULE = "deepspeed_tpu.ops.adam.cpu_adam_binding"


class CPUAdagradBuilder(NativeOpBuilder):
    NAME = "cpu_adagrad"
    MODULE = "deepspeed_tpu.ops.adagrad.cpu_adagrad_binding"


class AsyncIOBuilder(NativeOpBuilder):
    NAME = "async_io"
    MODULE = "deepspeed_tpu.ops.aio.aio_binding"


class UtilsBuilder(OpBuilder):
    NAME = "utils"
    MODULE = "deepspeed_tpu.ops.flatten"


class FusedAdamBuilder(OpBuilder):
    NAME = "fused_adam"
    MODULE = "deepspeed_tpu.ops.adam.fused_adam_kernel"


class FusedLambBuilder(OpBuilder):
    NAME = "fused_lamb"
    MODULE = "deepspeed_tpu.ops.lamb.fused_lamb_kernel"


class QuantizerBuilder(OpBuilder):
    NAME = "quantizer"
    MODULE = "deepspeed_tpu.ops.quantizer.kernels"


class RandomLTDBuilder(OpBuilder):
    NAME = "random_ltd"
    MODULE = "deepspeed_tpu.ops.random_ltd.dropping_utils"


class SparseAttnBuilder(OpBuilder):
    NAME = "sparse_attn"
    MODULE = "deepspeed_tpu.ops.sparse_attention.kernels"


class TransformerBuilder(OpBuilder):
    NAME = "transformer"
    MODULE = "deepspeed_tpu.ops.transformer.training_kernels"


class StochasticTransformerBuilder(OpBuilder):
    NAME = "stochastic_transformer"
    MODULE = "deepspeed_tpu.ops.transformer.training_kernels"


class InferenceBuilder(OpBuilder):
    NAME = "transformer_inference"
    MODULE = "deepspeed_tpu.ops.transformer.inference_kernels"


class SpatialInferenceBuilder(OpBuilder):
    NAME = "spatial_inference"
    MODULE = "deepspeed_tpu.ops.spatial.kernels"


_BUILDERS: Dict[str, Type[OpBuilder]] = {
    cls.__name__: cls
    for cls in (CPUAdamBuilder, CPUAdagradBuilder, AsyncIOBuilder, UtilsBuilder, FusedAdamBuilder, FusedLambBuilder,
                QuantizerBuilder, RandomLTDBuilder, SparseAttnBuilder, TransformerBuilder,
                StochasticTransformerBuilder, InferenceBuilder, SpatialInferenceBuilder)
}


def get_builder_class(class_name: str) -> Optional[Type[OpBuilder]]:
    return _BUILDERS.get(class_name)


def all_builder_names():
    return sorted(_BUILDERS)


def op_report() -> Dict[str, bool]:
    """Compatibility matrix for ds_report (reference env_report.py)."""
    return {name: cls().builder_available() for name, cls in _BUILDERS.items()}
