"""Native parallel flatten/unflatten/memcpy over host numpy buffers.

Reference parity: ``csrc/utils/flatten_unflatten.cpp`` (UtilsBuilder) and the
parallel ``deepspeed_memcpy`` from ``csrc/aio/py_lib/deepspeed_py_copy.cpp``.
The jnp equivalents for device arrays live in ``deepspeed_tpu.ops.flatten``;
these operate on pinned host staging buffers for the offload path.
"""

from __future__ import annotations

import ctypes
from typing import List, Sequence

import numpy as np

from deepspeed_tpu.ops import native
from deepspeed_tpu.ops.native import c_i64

_configured = False


def _lib():
    global _configured
    lib = native.get_lib()
    if not _configured:
        pp = ctypes.POINTER(ctypes.c_void_p)
        lib.ds_flatten.argtypes = [pp, ctypes.POINTER(c_i64), c_i64, ctypes.c_void_p]
        lib.ds_unflatten.argtypes = [pp, ctypes.POINTER(c_i64), c_i64, ctypes.c_void_p]
        lib.ds_memcpy.argtypes = [ctypes.c_void_p, ctypes.c_void_p, c_i64]
        _configured = True
    return lib


def _ptr_array(arrs: Sequence[np.ndarray]):
    arr_t = ctypes.c_void_p * len(arrs)
    return arr_t(*[a.ctypes.data_as(ctypes.c_void_p).value for a in arrs])


def _size_array(arrs: Sequence[np.ndarray]):
    sz_t = c_i64 * len(arrs)
    return sz_t(*[a.nbytes for a in arrs])


def flatten(tensors: Sequence[np.ndarray], out: np.ndarray | None = None) -> np.ndarray:
    """Parallel copy of ``tensors`` back-to-back into one flat buffer.

    Same-dtype inputs produce a flat array of that dtype; mixed dtypes
    produce a uint8 byte buffer.
    """
    if not tensors:
        return np.zeros(0, np.uint8)
    total = sum(t.nbytes for t in tensors)
    if out is None:
        dtypes = {t.dtype for t in tensors}
        if len(dtypes) == 1:
            out = np.empty(total // tensors[0].itemsize, tensors[0].dtype)
        else:
            out = np.empty(total, np.uint8)
    if out.nbytes < total:
        raise ValueError(f"output buffer has {out.nbytes} bytes, need {total}")
    tensors = [np.ascontiguousarray(t) for t in tensors]
    _lib().ds_flatten(ctypes.cast(_ptr_array(tensors), ctypes.POINTER(ctypes.c_void_p)),
                      _size_array(tensors), len(tensors),
                      out.ctypes.data_as(ctypes.c_void_p))
    return out


def unflatten(flat: np.ndarray, tensors: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Parallel scatter of ``flat`` into (newly allocated) arrays shaped like
    ``tensors``; writes in place when the targets are contiguous."""
    outs = [t if t.flags["C_CONTIGUOUS"] else np.empty_like(t) for t in tensors]
    _lib().ds_unflatten(ctypes.cast(_ptr_array(outs), ctypes.POINTER(ctypes.c_void_p)),
                        _size_array(outs), len(outs),
                        np.ascontiguousarray(flat).ctypes.data_as(ctypes.c_void_p))
    return outs


def memcpy(dst: np.ndarray, src: np.ndarray) -> None:
    """Multi-threaded memcpy for large host-buffer moves."""
    assert dst.nbytes == src.nbytes
    _lib().ds_memcpy(dst.ctypes.data_as(ctypes.c_void_p),
                     np.ascontiguousarray(src).ctypes.data_as(ctypes.c_void_p),
                     dst.nbytes)
