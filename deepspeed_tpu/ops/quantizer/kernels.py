"""Quantization kernels: grouped fake-quant + TPU stochastic rounding.

TPU-native named op for the reference's quantizer family
(``csrc/quantization/fake_quantizer.cu`` — ``ds_quantize_*`` /
``ds_sr_quantize_*`` grouped sym/asym fake quantization with
stochastic-rounding variants; binding ``csrc/quantization/pt_binding.cpp``).

Deterministic rounding is pure elementwise math — XLA fuses it, no kernel
needed (:func:`ds_quantize` / :func:`ds_quantize_asym`). Stochastic
rounding is where the hardware matters: the Pallas kernel draws uniform
noise from the on-core PRNG (``pltpu.prng_seed`` / ``prng_random_bits``)
right in VMEM — no HBM round-trip for a noise tensor the size of the
input, which is what an XLA-level ``jax.random.uniform`` would cost.
Off-TPU the same math runs with ``jax.random`` (bit-exact distribution
up to the underlying generator).

Group semantics mirror the reference: the tensor is flattened to
``[groups, -1]`` and each group gets one scale (sym) or scale+offset
(asym).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_ROWS = 8


def _group_view(x, groups: int):
    flat = x.astype(jnp.float32).reshape(groups, -1)
    L = flat.shape[1]
    pad = (-L) % _LANES
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    rpad = (-groups) % _ROWS
    if rpad:
        flat = jnp.pad(flat, ((0, rpad), (0, 0)))
    return flat, L, pad, rpad


def _sym_scale(flat, bits: int):
    qmax = 2.0 ** (bits - 1) - 1
    scale = jnp.max(jnp.abs(flat), axis=1, keepdims=True) / qmax
    return jnp.where(scale == 0, 1.0, scale), qmax


# ------------------------------------------------------------------ #
# deterministic (round-to-nearest) — XLA fuses this; no kernel needed

def ds_quantize(x, groups: int, bits: int = 8):
    """Grouped symmetric fake quantization (reference ``ds_quantize_fp32``)."""
    flat, L, pad, rpad = _group_view(x, groups)
    scale, qmax = _sym_scale(flat, bits)
    q = jnp.clip(jnp.round(flat / scale), -qmax - 1, qmax)
    out = (q * scale)[:groups, :L] if (pad or rpad) else q * scale
    return out.reshape(x.shape).astype(x.dtype)


def ds_quantize_asym(x, groups: int, bits: int = 8):
    """Grouped asymmetric fake quantization (reference ``ds_quantize_asym``)."""
    flat = x.astype(jnp.float32).reshape(groups, -1)
    lo = jnp.min(flat, axis=1, keepdims=True)
    hi = jnp.max(flat, axis=1, keepdims=True)
    levels = 2.0**bits - 1
    scale = jnp.where(hi > lo, (hi - lo) / levels, 1.0)
    q = jnp.clip(jnp.round((flat - lo) / scale), 0, levels)
    return (q * scale + lo).reshape(x.shape).astype(x.dtype)


# ------------------------------------------------------------------ #
# stochastic rounding — Pallas kernel drawing noise from the core PRNG

def _sr_kernel(seed_ref, x_ref, scale_ref, o_ref, *, qmax, n_cols):
    i, j = pl.program_id(0), pl.program_id(1)
    # mix the user seed (odd multiplicative hash, int32 wraparound is fine)
    # so seed=step streams don't collide with adjacent blocks' streams at
    # neighbouring steps
    pltpu.prng_seed(seed_ref[0] * 1000003 + i * n_cols + j)
    bits = pltpu.prng_random_bits(x_ref.shape)
    # prng_random_bits is int32: mask to the low 24 bits (non-negative
    # regardless of sign) → uniform [0, 1). An arithmetic >> of negative
    # draws would put u in [-0.5, 0) and bias every element low by half a
    # step.
    u = (bits & 0x00FFFFFF).astype(jnp.float32) * (1.0 / 16777216.0)
    scaled = x_ref[:] / scale_ref[:]
    q = jnp.clip(jnp.floor(scaled + u), -qmax - 1.0, qmax)
    o_ref[:] = q * scale_ref[:]


@functools.partial(jax.jit, static_argnames=("bits", "col_block", "interpret"))
def _sr_call(flat, scale, seed, *, bits, col_block, interpret):
    G, L = flat.shape
    qmax = 2.0 ** (bits - 1) - 1
    grid = (G // _ROWS, L // col_block)
    out = pl.pallas_call(
        functools.partial(_sr_kernel, qmax=qmax, n_cols=grid[1]),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((_ROWS, col_block), lambda i, j, sc: (i, j)),
                pl.BlockSpec((_ROWS, 1), lambda i, j, sc: (i, 0)),
            ],
            out_specs=pl.BlockSpec((_ROWS, col_block), lambda i, j, sc: (i, j)),
        ),
        out_shape=jax.ShapeDtypeStruct((G, L), jnp.float32),
        interpret=interpret,
    )(jnp.asarray(seed, jnp.int32).reshape(1), flat, scale)
    return out


def ds_sr_quantize(x, groups: int, bits: int = 8, seed=0,
                   interpret: Optional[bool] = None):
    """Grouped symmetric fake quantization with STOCHASTIC rounding
    (reference ``ds_sr_quantize_fp32``): values round up with probability
    equal to their fractional position, so quantization error is unbiased
    in expectation — the property 1-bit/low-precision training relies on.
    """
    # the core-PRNG primitives have no interpret-mode lowering, so the
    # kernel runs only where it compiles: on TPU (interpret=False forces
    # a compile attempt for AOT checks)
    use_kernel = (jax.default_backend() == "tpu" if interpret is None
                  else not interpret)
    flat, L, pad, rpad = _group_view(x, groups)
    scale, qmax = _sym_scale(flat[:groups] if rpad else flat, bits)
    if rpad:
        scale = jnp.pad(scale, ((0, rpad), (0, 0)), constant_values=1.0)
    if use_kernel:
        col_block = next(b for b in (1024, 512, 256, _LANES)
                         if flat.shape[1] % b == 0)
        out = _sr_call(flat, scale, seed, bits=bits, col_block=col_block,
                       interpret=False)
    else:
        u = jax.random.uniform(jax.random.key(seed), flat.shape)
        q = jnp.clip(jnp.floor(flat / scale + u), -qmax - 1, qmax)
        out = q * scale
    out = out[:groups, :L] if (pad or rpad) else out
    return out.reshape(x.shape).astype(x.dtype)


def ds_sr_quantize_asym(x, groups: int, bits: int = 8, seed=0):
    """Asymmetric stochastic-rounding fake quantization (jnp form; the sym
    kernel above is the hot path the reference accelerates)."""
    flat = x.astype(jnp.float32).reshape(groups, -1)
    lo = jnp.min(flat, axis=1, keepdims=True)
    hi = jnp.max(flat, axis=1, keepdims=True)
    levels = 2.0**bits - 1
    scale = jnp.where(hi > lo, (hi - lo) / levels, 1.0)
    u = jax.random.uniform(jax.random.key(seed), flat.shape)
    q = jnp.clip(jnp.floor((flat - lo) / scale + u), 0, levels)
    return (q * scale + lo).reshape(x.shape).astype(x.dtype)
