"""Weight-only int8 quantization for inference.

Reference parity: ``deepspeed/module_inject/replace_module.py:135``
(``GroupQuantizer`` — symmetric per-group int8 weights for ZeRO-Inference)
and the int8 paths of ``model_implementations``.

TPU design: a ``Quantized8`` pytree node holds the int8 payload plus f32
per-group scales. Because it is a pytree, ``lax.scan`` over stacked layer
weights slices the payload AND scales together, so dequantisation happens
per layer inside the compiled loop: HBM at rest holds int8 (4x smaller than
f32, 2x smaller than bf16) and the bf16 copy of one layer exists only
transiently. XLA fuses ``(q * scale).astype(bf16)`` into the consuming
matmul's operand read.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Quantized8:
    """Symmetric per-group int8 weight: ``w ~= q * scale`` (scale broadcast
    over the quantisation axis, which is always the LAST axis here)."""

    q: jax.Array          # int8, original shape
    scale: jax.Array      # f32, shape[:-1] + (groups,)

    def dequant(self, dtype=jnp.bfloat16) -> jax.Array:
        groups = self.scale.shape[-1]
        *lead, last = self.q.shape
        qg = self.q.reshape(*lead, groups, last // groups)
        w = qg.astype(jnp.float32) * self.scale[..., None]
        return w.reshape(*lead, last).astype(dtype)

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self):
        return self.q.size + self.scale.size * 4


def quantize_int8(w, groups: int = 1) -> Quantized8:
    """Symmetric per-(row x group) int8 quantisation over the last axis."""
    w = jnp.asarray(w)
    *lead, last = w.shape
    if last % groups:
        raise ValueError(f"last dim {last} not divisible by q_groups {groups}")
    wg = w.astype(jnp.float32).reshape(*lead, groups, last // groups)
    amax = jnp.max(jnp.abs(wg), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wg / scale[..., None]), -127, 127).astype(jnp.int8)
    return Quantized8(q=q.reshape(*lead, last), scale=scale)


def maybe_dequant(w: Any, dtype=jnp.bfloat16):
    """Transparent access used by the model zoo's matmul sites."""
    if isinstance(w, Quantized8):
        return w.dequant(dtype)
    return w


_QUANTIZABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_params(params, groups: int = 1, include_embed: bool = False):
    """Quantize the transformer weight matrices of a zoo param tree
    (attention + MLP projections; embeddings/norms/biases stay dense)."""

    def walk(tree, under_layers):
        if isinstance(tree, dict):
            out = {}
            for k, v in tree.items():
                if under_layers and k in _QUANTIZABLE and not isinstance(v, dict):
                    out[k] = quantize_int8(v, groups)
                else:
                    out[k] = walk(v, under_layers or k == "layers")
            return out
        return tree

    out = walk(params, False)
    if include_embed and isinstance(out, dict) and "lm_head" in out:
        out["lm_head"] = quantize_int8(out["lm_head"], groups)
    return out


def tree_nbytes(params) -> int:
    return sum(l.nbytes for l in jax.tree.leaves(params))
