"""Weight-only int8 quantization for inference.

Reference parity: ``deepspeed/module_inject/replace_module.py:135``
(``GroupQuantizer`` — symmetric per-group int8 weights for ZeRO-Inference)
and the int8 paths of ``model_implementations``.

TPU design: a ``Quantized8`` pytree node holds the int8 payload plus f32
per-group scales. Because it is a pytree, ``lax.scan`` over stacked layer
weights slices the payload AND scales together, so dequantisation happens
per layer inside the compiled loop: HBM at rest holds int8 (4x smaller than
f32, 2x smaller than bf16) and the bf16 copy of one layer exists only
transiently. XLA fuses ``(q * scale).astype(bf16)`` into the consuming
matmul's operand read.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Quantized8:
    """Symmetric per-group int8 weight: ``w ~= q * scale`` (scale broadcast
    over the quantisation axis, which is always the LAST axis here)."""

    q: jax.Array          # int8, original shape
    scale: jax.Array      # f32, shape[:-1] + (groups,)

    def dequant(self, dtype=jnp.bfloat16) -> jax.Array:
        groups = self.scale.shape[-1]
        *lead, last = self.q.shape
        qg = self.q.reshape(*lead, groups, last // groups)
        w = qg.astype(jnp.float32) * self.scale[..., None]
        return w.reshape(*lead, last).astype(dtype)

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self):
        return self.q.size + self.scale.size * 4


def quantize_int8(w, groups: int = 1) -> Quantized8:
    """Symmetric per-(row x group) int8 quantisation over the last axis."""
    w = jnp.asarray(w)
    *lead, last = w.shape
    if last % groups:
        raise ValueError(f"last dim {last} not divisible by q_groups {groups}")
    wg = w.astype(jnp.float32).reshape(*lead, groups, last // groups)
    amax = jnp.max(jnp.abs(wg), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wg / scale[..., None]), -127, 127).astype(jnp.int8)
    return Quantized8(q=q.reshape(*lead, last), scale=scale)


def maybe_dequant(w: Any, dtype=jnp.bfloat16):
    """Transparent access used by the model zoo's matmul sites."""
    if isinstance(w, Quantized8):
        return w.dequant(dtype)
    return w


# res_* are the PR-MoE dense-branch projections; the tiny gate/coef
# matrices stay dense (their cost is negligible and routing is
# numerically sensitive)
_QUANTIZABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                "res_w_up", "res_w_down")


def quantize_params(params, groups: int = 1, include_embed: bool = False):
    """Quantize the transformer weight matrices of a zoo param tree
    (attention + MLP projections; embeddings/norms/biases stay dense)."""

    def walk(tree, under_layers):
        if isinstance(tree, dict):
            out = {}
            for k, v in tree.items():
                if under_layers and k in _QUANTIZABLE and not isinstance(v, dict):
                    out[k] = quantize_int8(v, groups)
                else:
                    out[k] = walk(v, under_layers or k == "layers")
            return out
        return tree

    out = walk(params, False)
    if include_embed and isinstance(out, dict) and "lm_head" in out:
        out["lm_head"] = quantize_int8(out["lm_head"], groups)
    return out


def tree_nbytes(params) -> int:
    return sum(l.nbytes for l in jax.tree.leaves(params))


def _mesh_axis_size(mesh, entry) -> int:
    import math
    axes = entry if isinstance(entry, tuple) else (entry,)
    return math.prod(mesh.shape.get(a, 1) for a in axes)


def _pruned_spec(mesh, shape, spec):
    """Sanitized per-dim spec entries, padded with None to the array rank."""
    from deepspeed_tpu.runtime.zero.partition import sanitize_tp_spec
    out = list(sanitize_tp_spec(mesh, shape, spec) or ())
    return out + [None] * (len(shape) - len(out))


def align_quant_groups(params, tp_specs, mesh):
    """Subdivide Quantized8 scales so group boundaries align with the TP
    shard boundaries wherever the payload allows it.

    Splitting a quantisation group into ``r`` equal children with the parent's
    scale is numerically a no-op for dequantisation, so when the tp axis size
    does not divide ``q_groups`` the scales are repeated up to
    ``lcm(q_groups, axis)`` — keeping the quant axis SHARDED instead of hitting
    :func:`quantized_shardings`'s replicate fallback (a silent perf cliff the
    reference never has: its GroupQuantizer regroups at partition time,
    ``replace_module.py:42-135``).

    For any payload the sanitizer lets shard (axis | last) with any valid
    group count (groups | last), the lcm also divides the axis — alignment
    always succeeds; the untouched branch below is a safety guard for
    hand-built leaves that violate the quantize_int8 invariant."""
    import math

    from jax.sharding import PartitionSpec as P

    def one(leaf, spec):
        if not isinstance(leaf, Quantized8):
            return leaf
        qs = _pruned_spec(mesh, leaf.q.shape, P() if spec is None else spec)
        n = _mesh_axis_size(mesh, qs[-1]) if qs[-1] is not None else 1
        groups = leaf.scale.shape[-1]
        if n <= 1 or groups % n == 0:
            return leaf
        g2 = groups * n // math.gcd(groups, n)
        if leaf.q.shape[-1] % g2:
            return leaf          # genuinely indivisible: fallback handles it
        r = g2 // groups
        rep = np.repeat if isinstance(leaf.scale, np.ndarray) else jnp.repeat
        return Quantized8(q=leaf.q, scale=rep(leaf.scale, r, axis=-1))

    return jax.tree.map(one, params, tp_specs,
                        is_leaf=lambda x: isinstance(x, Quantized8))


_warned_misaligned: set = set()


def quantized_shardings(params, tp_specs, mesh):
    """Sharding tree for a (possibly partially) quantized param tree under
    tensor parallelism — the reference composes ``GroupQuantizer`` output with
    TP slicing inside ``replace_module.py:42-119``; here the composition is a
    consistency rule between the int8 payload and its per-group scales:

    - ``q`` shards exactly like the original weight's PartitionSpec;
    - ``scale`` (shape ``lead + (groups,)``) shards its lead dims the same
      way, and its groups axis like the weight's LAST (quantisation) axis —
      group boundaries align with shard boundaries iff the axis size divides
      ``groups``, otherwise the quant-axis sharding is dropped from BOTH so
      a shard never needs another shard's scales (callers should run
      :func:`align_quant_groups` first, which removes this case whenever the
      payload shape permits; a warning fires once per config if it remains).

    Mesh axes absent from the mesh or not dividing a dim are dropped
    (same policy as ``ZeroShardingRules.param_spec``). Returns a tree
    congruent with ``params`` (Quantized8 nodes carry NamedShardings).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    def one(leaf, spec):
        spec = P() if spec is None else spec
        if not isinstance(leaf, Quantized8):
            return NamedSharding(mesh, P(*_pruned_spec(mesh, leaf.shape, spec)))
        qs = _pruned_spec(mesh, leaf.q.shape, spec)
        groups = leaf.scale.shape[-1]
        last = qs[-1]
        if last is not None and groups % _mesh_axis_size(mesh, last):
            key = (groups, _mesh_axis_size(mesh, last))
            if key not in _warned_misaligned:
                _warned_misaligned.add(key)
                from deepspeed_tpu.utils.logging import logger
                logger.warning(
                    f"int8 x TP: q_groups={groups} not divisible by tp axis "
                    f"size {key[1]} — quant-axis sharding DROPPED (weights + "
                    "scales replicated on that axis). Run align_quant_groups "
                    "on the param tree first (lossless regrouping) or pick "
                    "q_groups a multiple of the tp size.")
            last = None          # shard/group boundaries misalign: replicate
        qs[-1] = last
        # scale lead dims == q lead dims (scale.shape = q.shape[:-1] + (groups,)),
        # so the pruned lead entries transfer; the groups axis takes `last`
        ss = qs[:-1] + [last]
        return Quantized8(q=NamedSharding(mesh, P(*qs)),
                          scale=NamedSharding(mesh, P(*ss)))

    return jax.tree.map(one, params, tp_specs,
                        is_leaf=lambda x: isinstance(x, Quantized8))
