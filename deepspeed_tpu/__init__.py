"""deepspeed_tpu — a TPU-native large-model training & inference framework.

Public API mirrors the reference (``deepspeed/__init__.py``):

    import deepspeed_tpu

    engine, optimizer, dataloader, lr_scheduler = deepspeed_tpu.initialize(
        model=my_model, model_parameters=params, config="ds_config.json")
    for batch in data:
        loss = engine.train_batch(batch)

    infer_engine = deepspeed_tpu.init_inference(model, tensor_parallel={"tp_size": 8})
"""

from deepspeed_tpu.version import __version__, git_branch, git_hash
from deepspeed_tpu.runtime import zero  # deepspeed.zero.Init / GatheredParameters parity
from deepspeed_tpu.utils.init_on_device import OnDevice  # deepspeed.OnDevice parity

# Reference top-level surface (deepspeed/__init__.py:14-34), resolved
# lazily (PEP 562) so `import deepspeed_tpu` stays light and cycle-free.
_LAZY_EXPORTS = {
    "DeepSpeedEngine": ("deepspeed_tpu.runtime.engine", "DeepSpeedEngine"),
    "PipelineEngine": ("deepspeed_tpu.runtime.pipe.engine", "PipelineEngine"),
    "InferenceEngine": ("deepspeed_tpu.inference.engine", "InferenceEngine"),
    "DeepSpeedInferenceConfig": ("deepspeed_tpu.inference.config",
                                 "DeepSpeedInferenceConfig"),
    "DeepSpeedConfig": ("deepspeed_tpu.config.core", "DeepSpeedConfig"),
    "DeepSpeedConfigError": ("deepspeed_tpu.config.core", "DeepSpeedConfigError"),
    "DeepSpeedTransformerLayer": ("deepspeed_tpu.ops.transformer.training_kernels",
                                  "DeepSpeedTransformerLayer"),
    "DeepSpeedTransformerConfig": ("deepspeed_tpu.ops.transformer.training_kernels",
                                   "DeepSpeedTransformerConfig"),
    "PipelineModule": ("deepspeed_tpu.runtime.pipe.module", "PipelineModule"),
    "init_distributed": ("deepspeed_tpu.comm", "init_distributed"),
    "log_dist": ("deepspeed_tpu.utils.logging", "log_dist"),
    "add_tuning_arguments": ("deepspeed_tpu.runtime.lr_schedules",
                             "add_tuning_arguments"),
    "checkpointing": ("deepspeed_tpu.runtime.activation_checkpointing.checkpointing",
                      None),
    "module_inject": ("deepspeed_tpu.module_inject", None),
    "ops": ("deepspeed_tpu.ops", None),
}


def __getattr__(name):
    entry = _LAZY_EXPORTS.get(name)
    if entry is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    mod = importlib.import_module(entry[0])
    obj = mod if entry[1] is None else getattr(mod, entry[1])
    globals()[name] = obj
    return obj


def __dir__():
    return sorted(list(globals()) + list(_LAZY_EXPORTS))


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               mesh=None,
               config_params=None):
    """Initialize the training engine (reference deepspeed/__init__.py:52).

    Returns ``(engine, optimizer, training_dataloader, lr_scheduler)``.
    """
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.utils.logging import log_dist

    log_dist(f"deepspeed_tpu info: version={__version__}", ranks=[0])

    if config is None:
        config = config_params
    if config is None and args is not None and hasattr(args, "deepspeed_config") and args.deepspeed_config:
        config = args.deepspeed_config

    if model is None:
        raise ValueError("deepspeed_tpu.initialize: model is required")

    engine_cls = DeepSpeedEngine
    if hasattr(model, "pipeline_spec"):
        from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
        engine_cls = PipelineEngine

    engine = engine_cls(model=model,
                        config=config,
                        model_parameters=model_parameters,
                        optimizer=optimizer,
                        lr_scheduler=lr_scheduler,
                        mesh=mesh,
                        mpu=mpu,
                        training_data=training_data,
                        collate_fn=collate_fn)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model, config=None, params=None, **kwargs):
    """Initialize the inference engine (reference deepspeed/__init__.py:214).

    ``params`` (a pytree) supplies the model weights explicitly; it is an
    engine argument, NOT a config field — folding it into the config dict
    would silently drop it and re-initialize random weights.
    """
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine

    if config is None:
        config = dict(kwargs)
    else:
        config = {**config, **kwargs}
    if "params" in config:
        # weights riding in the config dict are honored, never dropped
        cfg_params = config.pop("params")
        if params is None:
            params = cfg_params
    ds_inference_config = DeepSpeedInferenceConfig(**config)
    return InferenceEngine(model, config=ds_inference_config, params=params)


def add_config_arguments(parser):
    """Add --deepspeed / --deepspeed_config CLI args (reference :191)."""
    group = parser.add_argument_group("DeepSpeed-TPU", "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag to ease transition)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the framework json config file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated alias of --deepspeed")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated alias of --deepspeed_config")
    return parser
